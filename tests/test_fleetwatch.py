"""Fleet-health observability (ISSUE 6): the continuous drift auditor
flags injected cache/apiserver divergence and index corruption with the
right ``kind`` labels, the stranded-HBM gap matches brute-force
enumeration on random fleets, the scorecard reduces the decision
stream correctly, sampled verify (TPUSHARE_VERIFY_SAMPLE) actually
runs the oracles, and /inspect/fleet serves it all.
"""

import json
import random
import threading
import urllib.request

import pytest

from tests.test_contract import make_pod
from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.cache.index import EXCL_TIER, TIERS, summarize
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster
from tpushare.obs.fleetwatch import (
    AUDIT_SWEEPS, CACHE_DRIFT, FleetWatch, Scorecard, stranded_gap_mib)

HBM = 16384


def _fleet(n_nodes=2, chips=4, mesh="2x2"):
    fc = FakeCluster()
    for i in range(n_nodes):
        fc.add_tpu_node(f"n{i}", chips=chips, hbm_per_chip_mib=HBM,
                        mesh=mesh)
    cache = SchedulerCache(fc)
    cache.build_cache()
    return fc, cache


def _bind(fc, cache, node, name, hbm):
    info = cache.get_node_info(node)
    pod = fc.create_pod(make_pod(hbm=hbm, name=name))
    info.allocate(pod, fc)
    cache.add_or_update_pod(fc.get_pod("default", name))


def _drift_delta(fn):
    before = CACHE_DRIFT.snapshot()
    result = fn()
    after = CACHE_DRIFT.snapshot()
    delta = {k[0]: after[k] - before.get(k, 0.0)
             for k in after if after[k] != before.get(k, 0.0)}
    return result, delta


# -- drift auditor ------------------------------------------------------------

def test_clean_fleet_audits_zero_drift():
    fc, cache = _fleet()
    _bind(fc, cache, "n0", "w0", 2048)
    fw = FleetWatch(cache, cluster=fc, recheck_s=0.0)
    sweeps0 = AUDIT_SWEEPS.value
    _, delta = _drift_delta(lambda: fw.audit_sweep(sample=10))
    assert delta == {}
    assert AUDIT_SWEEPS.value == sweeps0 + 1


def test_auditor_flags_ghost_pod_within_one_sweep():
    fc, cache = _fleet()
    info = cache.get_node_info("n0")
    ghost = {"metadata": {"name": "ghost", "namespace": "default",
                          "uid": "ghost-uid",
                          "annotations": contract.placement_annotations(
                              [0], 2048, HBM)},
             "spec": {"nodeName": "n0"}}
    info.add_or_update_pod(ghost)
    fw = FleetWatch(cache, cluster=fc, recheck_s=0.0)
    r, delta = _drift_delta(lambda: fw.audit_sweep(sample=10))
    assert delta == {"ghost_pod": 1.0}
    assert [d["kind"] for d in r["drift"]] == ["ghost_pod"]
    # healed: the divergence disappears from the next sweep
    info.remove_pod(ghost)
    _, delta = _drift_delta(lambda: fw.audit_sweep(sample=10))
    assert delta == {}


def test_auditor_flags_missing_pod_and_chip_usage():
    fc, cache = _fleet()
    # missing: a bound, chip-annotated pod the cache never accounted
    p = make_pod(hbm=2048, name="lost")
    p["metadata"]["annotations"] = dict(
        p["metadata"].get("annotations") or {},
        **contract.placement_annotations([1], 2048, HBM))
    fc.create_pod(p)
    fc.bind_pod("default", "lost", "n0")
    fw = FleetWatch(cache, cluster=fc, recheck_s=0.0)
    _, delta = _drift_delta(lambda: fw.audit_sweep(sample=10))
    assert delta == {"missing_pod": 1.0}
    # usage mismatch: cache accounts the pod at the wrong size
    bound = fc.get_pod("default", "lost")
    skewed = json.loads(json.dumps(bound))  # deep copy
    skewed["metadata"]["annotations"][contract.ANN_HBM_POD] = "4096"
    cache.get_node_info("n0").add_or_update_pod(skewed)
    _, delta = _drift_delta(lambda: fw.audit_sweep(sample=10))
    assert delta == {"chip_usage": 1.0}


def test_auditor_flags_index_summary_corruption():
    fc, cache = _fleet()
    _bind(fc, cache, "n0", "w0", 2048)
    cache.index.flush()
    info = cache.get_node_info("n0")
    stamp, snap = info.stamped_snapshot()
    bogus = summarize(stamp, snap, info.topology, info.chip_count)
    bogus.n_ge = (0,) * (len(TIERS) + 1)
    bogus.contig_ge = (0,) * (len(TIERS) + 1)
    with cache.index._lock:
        cache.index._drop_locked("n0")
        cache.index._install_locked("n0", bogus)
    fw = FleetWatch(cache, cluster=fc, recheck_s=0.0)
    r, delta = _drift_delta(lambda: fw.audit_sweep(sample=10))
    assert delta.get("index_summary", 0.0) >= 1.0
    assert any(d["kind"] == "index_summary" for d in r["drift"])
    # heal: re-deriving the summary clears the drift
    cache.index.mark_dirty("n0")
    cache.index.flush()
    _, delta = _drift_delta(lambda: fw.audit_sweep(sample=10))
    assert delta == {}


def test_auditor_ignores_inflight_reservations():
    """A bind between phase 1 (reserve) and phase 3 (confirm) has no
    apiserver annotation yet — the auditor must not read that window
    as drift (reserved entries are excluded from audit_snapshot)."""
    fc, cache = _fleet()
    info = cache.get_node_info("n0")
    with info._lock:
        info.chips[0].reserve("inflight-uid", 4096)
        info._dirty()
    fw = FleetWatch(cache, cluster=fc, recheck_s=0.0)
    _, delta = _drift_delta(lambda: fw.audit_sweep(sample=10))
    assert delta == {}


def test_auditor_double_check_clears_transient_divergence():
    """Watch-lag shape: the truth catches up between the first pass and
    the recheck — nothing may be counted."""
    fc, cache = _fleet()
    info = cache.get_node_info("n0")
    ghost = {"metadata": {"name": "late", "namespace": "default",
                          "uid": "late-uid",
                          "annotations": contract.placement_annotations(
                              [0], 2048, HBM)},
             "spec": {"nodeName": "n0"}}
    info.add_or_update_pod(ghost)  # cache leads the apiserver briefly
    fw = FleetWatch(cache, cluster=fc, recheck_s=0.1)

    def heal():
        p = make_pod(hbm=2048, name="late", uid="late-uid")
        p["metadata"]["annotations"] = dict(
            p["metadata"].get("annotations") or {},
            **contract.placement_annotations([0], 2048, HBM))
        fc.create_pod(p)
        fc.bind_pod("default", "late", "n0")

    t = threading.Timer(0.02, heal)
    t.start()
    try:
        _, delta = _drift_delta(lambda: fw.audit_sweep(sample=10))
    finally:
        t.join()
    assert delta == {}


def test_audit_sweep_round_robin_covers_the_fleet():
    fc, cache = _fleet(n_nodes=5)
    fw = FleetWatch(cache, cluster=fc, recheck_s=0.0, audit_sample=2)
    seen: set[str] = set()
    for _ in range(3):
        seen.update(fw.audit_sweep()["nodes"])
    assert seen == {f"n{i}" for i in range(5)}


# -- stranded-HBM gap ---------------------------------------------------------

def _brute_gap(views, topo, hbm_per_chip):
    """Brute-force per-tier stranded gap: eligibility by direct scan,
    largest contiguous sub-box by full shape x position enumeration."""
    out = []
    for ti in range(len(TIERS) + 1):
        if ti == EXCL_TIER:
            elig = {v.idx for v in views
                    if v.healthy and v.used_hbm_mib == 0}
        else:
            elig = {v.idx for v in views
                    if v.healthy and v.free_hbm_mib >= TIERS[ti]}
        best = 0
        for size in range(len(views), 0, -1):
            if size <= best:
                break
            found = False
            for box in topo.box_shapes(size):
                for origin in topo.box_positions(box):
                    if all(i in elig
                           for i in topo.box_chips(origin, box)):
                        found = True
                        break
                if found:
                    break
            if found:
                best = size
        mib = hbm_per_chip if ti == EXCL_TIER else TIERS[ti]
        out.append((len(elig) - best) * mib)
    return out


@pytest.mark.parametrize("seed", range(6))
def test_stranded_gap_matches_bruteforce_on_random_fleets(seed):
    rng = random.Random(seed)
    mesh = rng.choice(["2x2", "4x2", "2x4", "4x4", None])
    chips = (int(mesh.split("x")[0]) * int(mesh.split("x")[1])
             if mesh else rng.choice([2, 4, 8]))
    fc = FakeCluster()
    names = [f"r{i}" for i in range(rng.randint(2, 5))]
    for n in names:
        fc.add_tpu_node(n, chips=chips, hbm_per_chip_mib=HBM, mesh=mesh)
    cache = SchedulerCache(fc)
    cache.build_cache()
    # random occupancy + health churn
    for n in names:
        info = cache.get_node_info(n)
        for cid in range(chips):
            if rng.random() < 0.6:
                used = rng.choice([512, 2048, 4096, 8192, HBM])
                info.add_or_update_pod({
                    "metadata": {"name": f"{n}-p{cid}", "namespace": "d",
                                 "uid": f"{n}-p{cid}",
                                 "annotations":
                                     contract.placement_annotations(
                                         [cid], used, HBM)},
                    "spec": {"nodeName": n}})
        if rng.random() < 0.3:
            info.set_unhealthy({rng.randrange(chips)})
    cache.index.flush()
    summaries = cache.index.summaries_snapshot()
    assert set(summaries) == set(names)
    for n in names:
        info = cache.get_node_info(n)
        _stamp, _non_tpu, n_ge, contig_ge, _r_ge = summaries[n]
        got = stranded_gap_mib(n_ge, contig_ge, info.hbm_per_chip)
        want = _brute_gap(info.snapshot(), info.topology,
                          info.hbm_per_chip)
        assert got == want, (n, got, want)


def test_sampler_reports_known_fragmented_layout():
    """docs/pd.md §1.3 literally: free chips with no free contiguous
    pair — the gap gauge must price exactly the stranded chip."""
    fc, cache = _fleet(n_nodes=1)
    # fill chips 0 and 3 (2x2 corners): free {1, 2} is a diagonal —
    # 2 schedulable chips, largest contiguous box 1
    for cid in (0, 3):
        cache.get_node_info("n0").add_or_update_pod({
            "metadata": {"name": f"fill{cid}", "namespace": "d",
                         "uid": f"fill{cid}",
                         "annotations": contract.placement_annotations(
                             [cid], HBM, HBM)},
            "spec": {"nodeName": "n0"}})
    fw = FleetWatch(cache, cluster=fc, recheck_s=0.0)
    sample = fw.sample_fleet()
    top = sample["tiers"][f">={HBM}MiB"]
    assert top["schedulable_chips"] == 2
    assert top["contiguous_chips"] == 1
    assert top["stranded_hbm_mib"] == HBM
    assert sample["tiers"]["exclusive"]["stranded_hbm_mib"] == HBM
    assert sample["top_fragmented"][0]["node"] == "n0"
    assert sample["top_fragmented"][0]["stranded_hbm_mib"] == HBM


# -- scorecard ----------------------------------------------------------------

def test_scorecard_reduces_the_decision_stream():
    clock = [0.0]
    sc = Scorecard(time_fn=lambda: clock[0])
    # pod a: filtered at t=0, bound at t=2
    sc.filter_recorded("a", ok=3, candidates=4)
    clock[0] = 2.0
    sc.bind_recorded("a", "bound")
    # pod b: rejected twice, then bound at t=10 (age 8 from first sight)
    sc.filter_recorded("b", ok=0, candidates=4)
    clock[0] = 6.0
    sc.filter_recorded("b", ok=0, candidates=4)
    clock[0] = 10.0
    sc.filter_recorded("b", ok=1, candidates=4)
    sc.bind_recorded("b", "bound")
    # pod c: still pending; one failed bind on d
    sc.filter_recorded("c", ok=0, candidates=4)
    sc.bind_recorded("d", "bind_failed")
    # utilization: 50% for 4s then 100% for 4s -> 75% time-weighted
    clock[0] = 0.0
    sc.util_sample(50, 100)
    clock[0] = 4.0
    sc.util_sample(50, 100)
    sc.util_sample(100, 100)
    clock[0] = 8.0
    sc.util_sample(100, 100)
    snap = sc.snapshot()
    assert snap["cycles"] == 5
    assert snap["rejected_cycles"] == 3
    assert snap["rejection_rate"] == pytest.approx(0.6)
    assert snap["binds"] == 2
    assert snap["bind_failures"] == 1
    assert snap["pending"] == 1
    assert snap["p99_pending_age_s"] == pytest.approx(8.0)
    # trapezoid: 50% over [0,4] + step to 100% at 4 + 100% over [4,8]
    assert snap["time_weighted_util_pct"] == pytest.approx(75.0)


# -- sampled verify -----------------------------------------------------------

def _poison_index(cache, name):
    """Install a wrong (all-zero) summary at the node's CURRENT stamp,
    through the real install path so buckets/prune maps/generation stay
    internally consistent — the index now wrongly prunes the node."""
    cache.index.flush()
    info = cache.get_node_info(name)
    stamp, snap = info.stamped_snapshot()
    bogus = summarize(stamp, snap, info.topology, info.chip_count)
    bogus.n_ge = (0,) * (len(TIERS) + 1)
    bogus.contig_ge = (0,) * (len(TIERS) + 1)
    with cache.index._lock:
        cache.index._drop_locked(name)
        cache.index._install_locked(name, bogus)


def _score_once(fc, cache):
    from tpushare.cache.nodeinfo import request_from_pod
    pod = fc.create_pod(make_pod(hbm=2048,
                                 name=f"probe{random.random()}"))
    req = request_from_pod(pod)
    return cache.score_nodes(pod, req, cache.node_names())


def test_sampled_verify_runs_the_index_oracle():
    from tpushare.cache.index import INDEX_STALE_SERVES
    fc = FakeCluster()
    fc.add_tpu_node("n0", chips=4, hbm_per_chip_mib=HBM, mesh="2x2")
    cache = SchedulerCache(fc, eqclass=False, verify_sample=1)
    cache.build_cache()
    _poison_index(cache, "n0")
    before = INDEX_STALE_SERVES.value
    scores, errors = _score_once(fc, cache)
    # the poisoned index pruned a schedulable node; the sampled oracle
    # full-scanned it and counted the stale prune
    assert scores.get("n0") is None and not errors
    assert INDEX_STALE_SERVES.value == before + 1


def test_unsampled_decisions_skip_the_oracle():
    from tpushare.cache.index import INDEX_STALE_SERVES
    fc = FakeCluster()
    fc.add_tpu_node("n0", chips=4, hbm_per_chip_mib=HBM, mesh="2x2")
    cache = SchedulerCache(fc, eqclass=False, verify_sample=0)
    cache.build_cache()
    _poison_index(cache, "n0")
    before = INDEX_STALE_SERVES.value
    _score_once(fc, cache)
    assert INDEX_STALE_SERVES.value == before


def test_verify_sample_cadence_is_one_in_n():
    from tpushare.cache.index import INDEX_STALE_SERVES
    fc = FakeCluster()
    fc.add_tpu_node("n0", chips=4, hbm_per_chip_mib=HBM, mesh="2x2")
    cache = SchedulerCache(fc, eqclass=False, verify_sample=3)
    cache.build_cache()
    _poison_index(cache, "n0")
    before = INDEX_STALE_SERVES.value
    for _ in range(6):  # calls 0..5: calls 0 and 3 draw the straw
        _score_once(fc, cache)
    assert INDEX_STALE_SERVES.value == before + 2


# -- /inspect/fleet -----------------------------------------------------------

def test_inspect_fleet_endpoint_and_gauges():
    fc, cache = _fleet()
    _bind(fc, cache, "n0", "w0", 4096)
    server = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    port = server.start()
    try:
        base = f"http://127.0.0.1:{port}"
        # drive one real cycle so the scorecard has a decision stream
        pod = fc.create_pod(make_pod(hbm=2048, name="cyc"))
        body = json.dumps({"Pod": pod,
                           "NodeNames": ["n0", "n1"]}).encode()
        req = urllib.request.Request(
            f"{base}/tpushare-scheduler/filter", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.loads(r.read())["NodeNames"]
        with urllib.request.urlopen(f"{base}/inspect/fleet",
                                    timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["nodes_covered"] == 2
        assert snap["tiers"][">=1MiB"]["schedulable_chips"] > 0
        assert snap["scorecard"]["cycles"] >= 1
        assert "drift_total" in snap["audit"]
        # prefixed route too (kube-ecosystem tooling hits the prefix)
        with urllib.request.urlopen(
                f"{base}/tpushare-scheduler/inspect/fleet",
                timeout=10) as r:
            assert json.loads(r.read())["nodes_covered"] == 2
        server.fleetwatch.sample_fleet()
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert 'tpushare_fleet_schedulable_chips{tier=">=1MiB"}' in text
        assert 'tpushare_fleet_stranded_hbm_mib' in text
        assert "tpushare_cache_drift_total" in text
        assert "tpushare_audit_sweeps_total" in text
    finally:
        server.stop()
