"""Zero-Python wire fast path (ISSUE 16): the native probe table.

The native table is a CACHE OF THE PYTHON PATH keyed by exact request
bytes and the cache mutation stamp. These tests pin the two safety
properties that make it deployable:

- **stamp seam** — any cache mutation between table sync and probe
  demotes that digest to the Python path (rc 0, zero bytes consumed);
  a hit is only ever the bytes the Python path would serve RIGHT NOW.
- **verify honesty** — with ``TPUSHARE_WIRE_VERIFY`` semantics on, a
  corrupted resident fragment is caught by the recompute-and-compare
  seam: the client gets the truth and the stale-serve counter moves.

Skipped wholesale when the shared object cannot be built (no g++) or
the wire entry points are absent (stale ``.so`` → graceful degrade).
"""

import hashlib
import http.client
import json
import random
import socket

import pytest

from tests.test_contract import make_pod
from tpushare.cache import SchedulerCache
from tpushare.core.native import engine as native_engine
from tpushare.extender.nativewire import (
    PROBE_BYPASS,
    PROBE_HIT,
    PROBE_INCOMPLETE,
    PROBE_MISS,
    NativeWireTable,
)
from tpushare.extender.server import ExtenderServer
from tpushare.extender.wirecache import WIRE_STALE_SERVES, _find_span
from tpushare.k8s import FakeCluster

pytestmark = pytest.mark.skipif(
    not native_engine.wire_probe_supported(),
    reason="native wire probe unavailable")

FILTER_PATH = "/tpushare-scheduler/filter"
PRIORITIZE_PATH = "/tpushare-scheduler/prioritize"


def http_bytes(path: str, body: bytes) -> bytes:
    """The exact frame a keep-alive kube-scheduler connection carries."""
    return (f"POST {path} HTTP/1.1\r\n"
            f"Content-Length: {len(body)}\r\n\r\n").encode() + body


def resp_body(resp: bytes) -> bytes:
    return resp.partition(b"\r\n\r\n")[2]


@pytest.fixture
def rig():
    fc = FakeCluster()
    for i in range(6):
        fc.add_tpu_node(f"n{i}", chips=4, hbm_per_chip_mib=16000,
                        mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    srv = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    assert srv.nativewire.enabled
    yield fc, cache, srv
    srv.nativewire.close()


def serve_py(srv, path: str, body: bytes) -> bytes:
    status, payload, _ = srv.handle_post(path, body)
    assert status == 200
    return payload


def prime(srv, path: str, body: bytes) -> bytes:
    """Serve through the Python path until the stamp settles: the first
    serve installs, but its own memo stash moves the stamp, so the
    SECOND serve re-installs under the now-stable stamp."""
    serve_py(srv, path, body)
    return serve_py(srv, path, body)


def test_probe_hit_is_byte_identical_to_python(rig):
    fc, cache, srv = rig
    names = [f"n{i}" for i in range(6)]
    for path in (FILTER_PATH, PRIORITIZE_PATH):
        body = json.dumps({"Pod": make_pod(hbm=2048),
                           "NodeNames": names}).encode()
        truth = prime(srv, path, body)
        raw = http_bytes(path, body)
        rc, resp, consumed = srv.nativewire.probe_request(bytearray(raw))
        assert rc == PROBE_HIT, path
        assert consumed == len(raw)
        assert resp_body(resp) == truth
        assert resp.startswith(b"HTTP/1.1 200 ")
        # a pipelined second copy: only the first frame is consumed
        rc2, _resp2, consumed2 = srv.nativewire.probe_request(
            bytearray(raw + raw))
        assert rc2 == PROBE_HIT
        assert consumed2 == len(raw)


def test_any_mutation_between_sync_and_probe_demotes(rig):
    """Property: over randomized mutate/probe interleavings, a moved
    stamp ALWAYS demotes the digest to the Python path, and a hit is
    ALWAYS byte-equal to what the Python path serves at that instant."""
    fc, cache, srv = rig
    rng = random.Random(1234)
    names = [f"n{i}" for i in range(6)]
    body = json.dumps({"Pod": make_pod(hbm=512),
                       "NodeNames": names}).encode()
    raw = http_bytes(FILTER_PATH, body)
    demoted = 0
    for trial in range(40):
        truth = prime(srv, FILTER_PATH, body)
        if rng.random() < 0.5:
            node = f"n{rng.randrange(6)}"
            cache.get_node_info(node).allocate(
                fc.create_pod(make_pod(hbm=64, name=f"mut-{trial}")), fc)
            rc, resp, consumed = srv.nativewire.probe_request(
                bytearray(raw))
            # the mutation moved the stamp: even if the verdict bytes
            # would not change, the probe must fall back — never a
            # maybe-stale serve
            assert rc == PROBE_MISS, trial
            assert resp is None and consumed == 0
            demoted += 1
            # the Python path re-arms the table; the next probe serves
            # the POST-mutation truth
            truth = prime(srv, FILTER_PATH, body)
        rc, resp, consumed = srv.nativewire.probe_request(bytearray(raw))
        assert rc == PROBE_HIT, trial
        assert resp_body(resp) == truth, trial
    assert demoted >= 10  # the rng actually exercised the seam


def test_poisoned_fragment_is_caught_by_verify(rig):
    """TPUSHARE_WIRE_VERIFY semantics end-to-end over a real socket: a
    corrupted resident entry must never reach a client — the recompute
    seam serves the truth and counts one stale serve."""
    fc, cache, srv = rig
    port = srv.start()
    try:
        srv.nativewire.verify = True
        names = [f"n{i}" for i in range(6)]
        body = json.dumps({"Pod": make_pod(hbm=1024),
                           "NodeNames": names}).encode()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)

        def post() -> tuple[int, bytes]:
            conn.request("POST", FILTER_PATH, body,
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, r.read()

        post()
        _, truth = post()  # stamp settled: table armed
        s, e = _find_span(body)
        span_d = hashlib.blake2b(body[s:e], digest_size=16).digest()
        h = hashlib.blake2b(body[:s], digest_size=16)
        h.update(body[e:])
        poison = b'{"Error": "poisoned fragment"}'
        srv.nativewire.install(span_d, h.digest(), "filter",
                               cache.mutation_stamp(), poison)
        assert srv.nativewire.stats()["installs"] >= 2  # poison resident
        stale0 = WIRE_STALE_SERVES.value
        status, served = post()
        conn.close()
        assert status == 200
        assert served == truth  # the client saw the truth, not poison
        assert b"poisoned" not in served
        assert WIRE_STALE_SERVES.value == stale0 + 1
    finally:
        srv.stop()


def test_kill_switch_env_disables(monkeypatch):
    monkeypatch.setenv("TPUSHARE_NO_NATIVE_WIRE", "1")
    assert not native_engine.wire_probe_supported()
    t = NativeWireTable(lambda: 0)
    assert not t.enabled
    assert t.stats()["enabled"] is False
    t.close()


def test_probe_protocol_edges():
    """Framing verdicts on a bare table: ineligible or incomplete input
    never consumes bytes and never fabricates a response."""
    t = NativeWireTable(lambda: 7)
    try:
        # partial head: wait for more bytes
        rc, resp, consumed = t.probe_request(bytearray(b"POST /tpush"))
        assert (rc, resp, consumed) == (PROBE_INCOMPLETE, None, 0)
        # non-POST and non-fast-path routes: hand to the Python stack
        for frame in (b"GET /metrics HTTP/1.1\r\n\r\n",
                      b"POST /tpushare-scheduler/bind HTTP/1.1\r\n"
                      b"Content-Length: 2\r\n\r\n{}"):
            rc, resp, consumed = t.probe_request(bytearray(frame))
            assert (rc, resp, consumed) == (PROBE_BYPASS, None, 0)
        body = b'{"Pod": {}, "NodeNames": ["a"]}'
        # Connection: close wants a one-shot response envelope the
        # resident fragment does not carry — bypass
        framed = (b"POST /tpushare-scheduler/filter HTTP/1.1\r\n"
                  b"Connection: close\r\n"
                  + f"Content-Length: {len(body)}\r\n\r\n".encode()
                  + body)
        rc, resp, consumed = t.probe_request(bytearray(framed))
        assert (rc, resp, consumed) == (PROBE_BYPASS, None, 0)
        # a well-framed filter nobody installed: plain miss
        raw = http_bytes(FILTER_PATH, body)
        rc, resp, consumed = t.probe_request(bytearray(raw))
        assert (rc, resp, consumed) == (PROBE_MISS, None, 0)
        # truncated body: wait, don't guess
        rc, resp, consumed = t.probe_request(bytearray(raw[:-4]))
        assert (rc, resp, consumed) == (PROBE_INCOMPLETE, None, 0)
    finally:
        t.close()


@pytest.mark.skipif(not hasattr(socket, "SO_REUSEPORT"),
                    reason="platform lacks SO_REUSEPORT")
def test_reuseport_two_listeners_share_one_port(monkeypatch):
    """Two full extender servers bind the SAME port under
    TPUSHARE_REUSEPORT=1 and both actually receive connections (the
    kernel balances per-connection across listeners)."""
    monkeypatch.setenv("TPUSHARE_REUSEPORT", "1")

    def build():
        fc = FakeCluster()
        for i in range(4):
            fc.add_tpu_node(f"r{i}", chips=4, hbm_per_chip_mib=16000,
                            mesh="2x2")
        cache = SchedulerCache(fc)
        cache.build_cache()
        return ExtenderServer(cache, fc, host="127.0.0.1", port=0)

    srv1 = build()
    port = srv1.start()
    srv2 = build()
    srv2.port = port
    try:
        assert srv2.start() == port
        assert srv1._httpd.reuseport_active
        assert srv2._httpd.reuseport_active
        body = json.dumps({"Pod": make_pod(hbm=256),
                           "NodeNames": [f"r{i}" for i in range(4)]
                           }).encode()
        answers = set()
        for _ in range(40):
            # fresh connection each time: a fresh 4-tuple re-rolls the
            # kernel's listener choice
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            c.request("POST", FILTER_PATH, body,
                      {"Content-Type": "application/json"})
            r = c.getresponse()
            answers.add(r.read())
            assert r.status == 200
            c.close()
        assert len(answers) == 1  # byte-identical verdicts across both
        seen1 = srv1.nativewire.stats()["probes"]
        seen2 = srv2.nativewire.stats()["probes"]
        assert seen1 + seen2 == 40
        assert seen1 > 0 and seen2 > 0  # p(all-one-listener) ~ 2^-39
    finally:
        srv1.stop()
        srv2.stop()
