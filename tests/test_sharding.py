"""Active-active shard membership tests (the scale-out tentpole).

Three layers:

1. unit — the membership/ring/pending bookkeeping driven directly
   (``_apply_membership``), pinning the handover-revalidation protocol:
   a newly owned node binds lock-free only after its generation stamp is
   observed unchanged (the node quiesced), a moving stamp keeps it on
   the claim-CAS path;
2. lease machinery over FakeCluster — N replicas each renewing their own
   ``tpushare-schd-shard-*`` lease converge on one membership, partition
   their fleet disjointly, and a replica that cannot renew steps itself
   down within one lease duration;
3. chaos handoff (the ISSUE satellite) — three COMPLETE extender stacks
   over the stub apiserver storm concurrent binds while one replica is
   killed (thread death, no abdication — the crash model): its shard is
   re-owned within one lease TTL, no bind lands on the dead server, and
   the apiserver-truth audit shows zero oversubscription across the
   handoff.
"""

import threading
import time

import pytest

from tests.test_ha_storm import (
    CHIPS, GIB, HBM, NODES, assert_apiserver_invariants, post, seed_pod,
    wait_until)
from tpushare.cache import SchedulerCache
from tpushare.controller import Controller
from tpushare.extender.server import ExtenderServer
from tpushare.ha.sharding import (
    SHARD_CONFLICTS, SHARD_LEASE_PREFIX, ShardMembership)
from tpushare.k8s import FakeCluster
from tpushare.k8s.client import ApiError
from tpushare.k8s.incluster import InClusterClient
from tpushare.k8s.stubapi import StubApiServer

FAST = dict(lease_duration=0.8, renew_period=0.1, retry_period=0.05)


# -- unit: ring/pending bookkeeping, no threads -------------------------------

class _Info:
    def __init__(self, version):
        self.version = version


class _FakeCache:
    """node_names/peek_node/set_ownership — just enough cache for the
    revalidation protocol."""

    def __init__(self, names):
        self._v = {n: (1, 0) for n in names}
        self.ownership = []  # set_ownership calls, in order

    def node_names(self):
        return list(self._v)

    def peek_node(self, name):
        v = self._v.get(name)
        return None if v is None else _Info(v)

    def bump(self, name):
        epoch, count = self._v[name]
        self._v[name] = (epoch, count + 1)

    def set_ownership(self, owned):
        self.ownership.append(owned)


def _member(identity, cache=None, cluster=None):
    return ShardMembership(cluster or FakeCluster(), identity,
                           cache=cache, **FAST)


def test_no_cache_single_member_owns_everything_lock_free():
    sm = _member("ra")
    sm._apply_membership(["ra"])
    assert sm.is_live() and sm.is_ring_leader()
    for n in ("a", "b", "zz"):
        assert sm.is_owned(n) and sm.owns_for_bind(n)


def test_first_membership_arms_pending_then_promotes_on_quiesce():
    cache = _FakeCache(["n1", "n2"])
    sm = _member("ra", cache=cache)
    sm._apply_membership(["ra"])
    # every owned node starts pending (this replica did not schedule its
    # recent history), stamped at rebalance time
    assert sm.snapshot()["pending_revalidation"] == 2
    # stamp unchanged since the rebalance -> quiesced -> promoted
    assert sm.owns_for_bind("n1")
    assert sm.snapshot()["pending_revalidation"] == 1
    # a node still being written by the old owner keeps the CAS...
    cache.bump("n2")
    assert not sm.owns_for_bind("n2")  # re-armed with the new stamp
    cache.bump("n2")
    assert not sm.owns_for_bind("n2")  # still moving
    # ...until it finally quiesces between two observations
    assert sm.owns_for_bind("n2")
    assert sm.snapshot()["pending_revalidation"] == 0


def test_note_bound_promotes_node_under_sustained_bind_traffic():
    # Every bind moves the node's stamp, so without note_bound a busy
    # pending node re-arms on every check and NEVER leaves the CAS path
    # (each check-to-check window contains our own previous bind).
    # BindHandler reports its own successful bind via note_bound; the
    # next check then sees a quiet window and promotes.
    cache = _FakeCache(["n1"])
    sm = _member("ra", cache=cache)
    sm._apply_membership(["ra"])
    cache.bump("n1")  # old-owner straggler: the rebalance stamp is stale
    # bind 1: the check re-arms on the moved stamp -> CAS path
    assert not sm.owns_for_bind("n1")
    cache.bump("n1")     # our bind's own mutation...
    sm.note_bound("n1")  # ...reported by BindHandler
    # bind 2: only OUR write happened since -> promoted, lock-free
    assert sm.owns_for_bind("n1")
    assert sm.snapshot()["pending_revalidation"] == 0


def test_note_bound_does_not_mask_foreign_writes():
    cache = _FakeCache(["n1"])
    sm = _member("ra", cache=cache)
    sm._apply_membership(["ra"])
    cache.bump("n1")
    assert not sm.owns_for_bind("n1")  # armed
    cache.bump("n1")
    sm.note_bound("n1")
    cache.bump("n1")  # a straggler lands AFTER our bind was noted
    assert not sm.owns_for_bind("n1")  # re-armed: CAS kept
    assert sm.owns_for_bind("n1")      # quiesces -> promotes
    # note_bound on an already-promoted node is a no-op
    sm.note_bound("n1")
    assert sm.owns_for_bind("n1")


def test_rebalance_arms_only_handed_over_nodes():
    names = [f"n{i}" for i in range(40)]
    cache = _FakeCache(names)
    sm = _member("ra", cache=cache)
    sm._apply_membership(["ra"])
    for n in names:
        assert sm.owns_for_bind(n)  # revalidate everything once
    # rb leaves: ra is handed rb's nodes, but its continuously-owned
    # ones must NOT re-enter pending
    sm._apply_membership(["ra", "rb"])
    owned_through = [n for n in names if sm.is_owned(n)]
    for n in owned_through:
        assert sm.owns_for_bind(n)
    sm._apply_membership(["ra"])
    handed = [n for n in names if n not in owned_through]
    assert sm.snapshot()["pending_revalidation"] == len(handed)
    # ownership refresh reached the cache on every rebalance
    assert len(cache.ownership) == 3
    assert cache.ownership[-1] == sm.is_owned


def test_not_in_membership_means_not_live_and_nothing_owned():
    sm = _member("ra", cache=_FakeCache(["n1"]))
    sm._apply_membership(["rb", "rc"])
    assert not sm.is_live() and not sm.is_owned("n1")
    assert not sm.owns_for_bind("n1")
    # dropped out of the ring entirely -> ownership predicate cleared
    assert sm._cache.ownership[-1] is None


def test_unknown_node_never_promotes_to_lock_free():
    # peek_node -> None means the cache cannot vouch for quiescence;
    # such a node stays on the claim-CAS path forever (it cannot pass
    # Filter anyway, so the only cost is safety)
    cache = _FakeCache(["n1"])
    sm = _member("ra", cache=cache)
    sm._apply_membership(["ra"])
    sm._pending["ghost"] = None
    assert not sm.owns_for_bind("ghost")
    assert not sm.owns_for_bind("ghost")


# -- lease machinery over FakeCluster -----------------------------------------

@pytest.fixture
def pair():
    fc = FakeCluster()
    for i in range(8):
        fc.add_tpu_node(f"n{i}", chips=4, hbm_per_chip_mib=16 * GIB)
    a = ShardMembership(fc, "ra", **FAST)
    b = ShardMembership(fc, "rb", **FAST)
    a.start()
    b.start()
    try:
        yield fc, a, b
    finally:
        a.stop()
        b.stop()


def test_two_replicas_converge_and_partition_disjointly(pair):
    fc, a, b = pair
    assert wait_until(lambda: a.members() == ("ra", "rb")
                      and b.members() == ("ra", "rb"))
    names = [f"n{i}" for i in range(8)]
    for n in names:
        # both replicas compute the same owner, exactly one owns it
        assert a.owner_of(n) == b.owner_of(n)
        assert a.is_owned(n) != b.is_owned(n)
    # exactly one ring leader (the defrag seat)
    assert a.is_ring_leader() != b.is_ring_leader()
    # each wrote its own lease
    leases = fc.list_leases(a.namespace)
    held = sorted((lease["metadata"]["name"] for lease in leases
                   if (lease.get("spec") or {}).get("holderIdentity")))
    assert held == [SHARD_LEASE_PREFIX + "ra", SHARD_LEASE_PREFIX + "rb"]


def test_clean_stop_releases_lease_and_peer_reowns(pair):
    fc, a, b = pair
    assert wait_until(lambda: a.members() == ("ra", "rb")
                      and b.members() == ("ra", "rb"))
    a.stop()  # abdication clears the holder: no TTL wait needed
    assert wait_until(lambda: b.members() == ("rb",))
    assert all(b.is_owned(f"n{i}") for i in range(8))
    assert b.is_ring_leader()


class _Partitioned:
    """Cluster proxy whose lease verbs fail while .down is set (the
    replica-side partition model: the stub keeps running, this replica
    just cannot reach it)."""

    def __init__(self, inner):
        self._inner = inner
        self.down = False

    def __getattr__(self, name):
        fn = getattr(self._inner, name)
        if name in ("get_lease", "create_lease", "update_lease",
                    "list_leases") and callable(fn):
            def wrapped(*a, **k):
                if self.down:
                    raise ApiError(500, "partitioned")
                return fn(*a, **k)
            return wrapped
        return fn


def test_partitioned_replica_steps_itself_down_within_ttl():
    fc = FakeCluster()
    for i in range(4):
        fc.add_tpu_node(f"n{i}", chips=4, hbm_per_chip_mib=16 * GIB)
    link = _Partitioned(fc)
    a = ShardMembership(link, "ra", **FAST)
    b = ShardMembership(fc, "rb", **FAST)
    a.start()
    b.start()
    try:
        assert wait_until(lambda: a.members() == ("ra", "rb")
                          and b.members() == ("ra", "rb"))
        link.down = True
        # within one lease duration the partitioned replica must stop
        # claiming ownership (peers have expired it and re-own its
        # shard; a stale lock-free owner would be split-brain)
        assert wait_until(lambda: not a.is_live(),
                          timeout=4 * FAST["lease_duration"])
        assert not any(a.is_owned(f"n{i}") for i in range(4))
        assert wait_until(lambda: b.members() == ("rb",),
                          timeout=4 * FAST["lease_duration"])
        assert all(b.is_owned(f"n{i}") for i in range(4))
        # healing the partition re-admits it, with revalidation pending
        link.down = False
        assert wait_until(lambda: a.is_live()
                          and a.members() == ("ra", "rb"))
    finally:
        a.stop()
        b.stop()


# -- chaos handoff: kill a replica mid-storm (the ISSUE satellite) ------------

class ShardReplica:
    """A complete extender stack whose HA mode is active-active."""

    def __init__(self, stub, ident: str):
        self.ident = ident
        self.client = InClusterClient(base_url=stub.base_url, timeout=10.0)
        self.cache = SchedulerCache(self.client)
        self.controller = Controller(self.client, self.cache)
        self.controller.build_cache()
        self.controller.start()
        self.sharding = ShardMembership(
            self.client, ident, cache=self.cache,
            on_rebalance=self.controller.resync_once, **FAST)
        self.sharding.start()
        self.server = ExtenderServer(self.cache, self.client,
                                     host="127.0.0.1", port=0,
                                     sharding=self.sharding)
        self.base = (f"http://127.0.0.1:{self.server.start()}"
                     "/tpushare-scheduler")

    def crash(self):
        """Process-death model: the membership thread dies WITHOUT
        abdicating (peers must expire the lease by TTL) and the HTTP
        server stops answering."""
        self.sharding._stop.set()
        if self.sharding._thread is not None:
            self.sharding._thread.join(timeout=5)
        self.server.stop()
        self.controller.stop()

    def stop(self):
        self.server.stop()
        self.sharding.stop()
        self.controller.stop()


def try_schedule_sharded(replicas, pod, node_names, attempts=80):
    """kube-scheduler across an active-active service: EVERY live
    replica serves filter+bind (no leader gate) — on error try the
    next endpoint."""
    name = pod["metadata"]["name"]
    ns = pod["metadata"]["namespace"]
    for i in range(attempts):
        rep = replicas[i % len(replicas)]
        try:
            _, flt = post(rep.base, "/filter",
                          {"Pod": pod, "NodeNames": node_names}, timeout=5)
        except OSError:
            continue
        ok = flt.get("NodeNames") or []
        if not ok:
            return None
        try:
            status, result = post(rep.base, "/bind", {
                "PodName": name, "PodNamespace": ns,
                "PodUID": pod["metadata"].get("uid", ""), "Node": ok[0]},
                timeout=5)
        except OSError:
            continue
        if status == 200 and not result.get("Error"):
            return ok[0]
        time.sleep(0.05)
    return None


@pytest.mark.slow
def test_chaos_shard_handoff_mid_storm():
    stub = StubApiServer().start()
    n_nodes = 8  # wide enough that all three shards are non-empty
    for i in range(n_nodes):
        stub.seed("nodes", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": f"s{i}",
                         "labels": {"tpushare": "true",
                                    "tpushare.aliyun.com/mesh": "2x2"}},
            "status": {"capacity": {
                "aliyun.com/tpu-hbm": str(CHIPS * HBM),
                "aliyun.com/tpu-count": str(CHIPS)}}})
    replicas = [ShardReplica(stub, f"r{c}") for c in "abc"]
    killed = []
    try:
        idents = tuple(sorted(r.ident for r in replicas))
        assert wait_until(lambda: all(r.sharding.members() == idents
                                      for r in replicas), timeout=10), \
            [r.sharding.members() for r in replicas]

        names = [f"s{i}" for i in range(n_nodes)]
        pods = [seed_pod(stub, f"chaos-{i}", 2 * GIB) for i in range(30)]
        bound: dict[str, str] = {}
        lock = threading.Lock()
        done = {"n": 0}
        live = list(replicas)

        def worker(chunk):
            for pod in chunk:
                node = try_schedule_sharded(list(live), pod, names)
                with lock:
                    done["n"] += 1
                    if node:
                        bound[pod["metadata"]["name"]] = node

        threads = [threading.Thread(target=worker, args=(pods[i::3],))
                   for i in range(3)]
        for t in threads:
            t.start()
        # kill one replica while binds are in flight (crash, not stop:
        # its lease holder stays set until the TTL expires it). Pick a
        # victim that actually owns part of the fleet so the handoff
        # moves real ownership.
        assert wait_until(lambda: done["n"] >= 8, timeout=30)
        victim = next(r for r in replicas
                      if any(r.sharding.is_owned(n) for n in names))
        victim_nodes = [n for n in names if victim.sharding.is_owned(n)]
        victim.crash()
        killed.append(victim)
        with lock:
            live[:] = [r for r in replicas if r is not victim]

        # the dead replica's shard is re-owned within ~one lease TTL
        # (expiry) + one renew period (the next membership poll)
        t0 = time.monotonic()
        survivors = [r for r in replicas if r is not victim]
        surviving = tuple(sorted(r.ident for r in survivors))
        assert wait_until(
            lambda: all(r.sharding.members() == surviving
                        for r in survivors),
            timeout=3 * FAST["lease_duration"]), \
            [r.sharding.members() for r in survivors]
        reowned_in = time.monotonic() - t0
        for n in names:
            owners = [r.ident for r in survivors if r.sharding.is_owned(n)]
            assert len(owners) == 1, (n, owners)
        assert reowned_in <= 3 * FAST["lease_duration"], reowned_in
        assert victim_nodes, "victim owned nothing — kill proved nothing"

        for t in threads:
            t.join(timeout=60)
        # kube-scheduler retries pending pods; drain the remainder
        # through the survivors before judging
        for pod in pods:
            name = pod["metadata"]["name"]
            if name not in bound:
                node = try_schedule_sharded(survivors, pod, names,
                                            attempts=40)
                if node:
                    bound[name] = node

        # capacity: 8 nodes x 4 chips x 16 GiB / 2 GiB = 256 slots >>
        # 30 pods — after the retry pass a strong majority must land
        assert len(bound) >= 26, f"storm bound only {len(bound)}/30"
        # the apiserver-truth audit: zero oversubscription across the
        # handoff, every placement consistent with its binding
        per_chip = assert_apiserver_invariants(stub, survivors[0].client)
        assert sum(per_chip.values()) == len(bound) * 2 * GIB
        for pod in survivors[0].client.list_pods():
            name = pod["metadata"]["name"]
            if name in bound:
                assert pod["spec"]["nodeName"] == bound[name]
        # the bind paths actually split owned/spillover (active-active
        # proof: more than one replica bound lock-free is not required,
        # but SOME owned-path binds must have happened)
        snap = survivors[0].sharding.snapshot()
        assert snap["conflicts"]["owned"] + snap["conflicts"]["spillover"] \
            > 0
    finally:
        for r in replicas:
            if r not in killed:
                r.stop()
        stub.stop()


def test_single_replica_stack_binds_lock_free():
    """The satellite closing the BENCH_r05 gap: a ring of size 1 owns
    everything, so the claim CAS is skipped even though HA is on."""
    stub = StubApiServer().start()
    stub.seed("nodes", {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": "s0",
                     "labels": {"tpushare": "true",
                                "tpushare.aliyun.com/mesh": "2x2"}},
        "status": {"capacity": {
            "aliyun.com/tpu-hbm": str(CHIPS * HBM),
            "aliyun.com/tpu-count": str(CHIPS)}}})
    rep = ShardReplica(stub, "solo")
    try:
        assert wait_until(lambda: rep.sharding.members() == ("solo",),
                          timeout=10)
        # the first membership arms revalidation even on a solo ring
        # (this replica cannot know it scheduled the node's history);
        # drive it to promotion — the node quiesces between two checks
        assert wait_until(lambda: rep.sharding.owns_for_bind("s0"),
                          timeout=10)
        owned_before = SHARD_CONFLICTS.get("owned")
        pod = seed_pod(stub, "solo-pod", 2 * GIB)
        assert try_schedule_sharded([rep], pod, ["s0"]) == "s0"
        assert SHARD_CONFLICTS.get("owned") == owned_before + 1
        # lock-free bind leaves NO claim annotation to GC later
        node = rep.client.get_node("s0")
        claims = (node["metadata"].get("annotations") or {}).get(
            "tpushare.aliyun.com/claims")
        assert not claims, claims
        assert_apiserver_invariants(stub, rep.client)
    finally:
        rep.stop()
        stub.stop()
