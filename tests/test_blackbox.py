"""Fleet black box (ISSUE 19): the native event ring and its pump.

PR 16's zero-Python steady state made the fast path invisible — a
digest-hit Filter is served with the GIL released and leaves no trace,
no explain record and no honest latency sample. These tests pin the
properties that make the ABI v8 ring + RingPump a truthful fix:

- **overflow is loud, never corrupt** — a full ring drops and counts;
  every drained event still decodes (``tpushare_blackbox_dropped_total``
  carries the loss, the data carries no garbage);
- **one serve, one sample** — with the pump running, the phase
  histogram gets exactly one observation per probe (the ring's native
  tick delta), not the Python envelope on top;
- **zero unexplained pods** — a native-heavy storm over a real socket
  leaves every pod with a truthful ``source: native`` explain record
  (the regression this PR exists to close).

Skipped wholesale when the shared object lacks the v8 entry points
(stale ``.so`` → graceful degrade).
"""

import http.client
import json

import pytest

from tests.test_contract import make_pod
from tpushare.cache import SchedulerCache
from tpushare.core.native import engine as native_engine
from tpushare.extender import nativewire
from tpushare.extender.nativewire import PROBE_HIT
from tpushare.extender.server import ExtenderServer
from tpushare.k8s import FakeCluster
from tpushare.obs import blackbox as bb

pytestmark = pytest.mark.skipif(
    not (native_engine.wire_probe_supported()
         and native_engine.blackbox_supported()),
    reason="native black-box ring unavailable")

FILTER_PATH = "/tpushare-scheduler/filter"
NAMES = [f"n{i}" for i in range(6)]


def http_bytes(path: str, body: bytes) -> bytes:
    return (f"POST {path} HTTP/1.1\r\n"
            f"Content-Length: {len(body)}\r\n\r\n").encode() + body


def drain_raw(batch: int = 1024) -> list[tuple]:
    rows = []
    while True:
        got = native_engine.blackbox_drain(batch)
        if not got:
            return rows
        rows.extend(got)


@pytest.fixture
def rig():
    fc = FakeCluster()
    for i in range(6):
        fc.add_tpu_node(f"n{i}", chips=4, hbm_per_chip_mib=16000,
                        mesh="2x2")
    cache = SchedulerCache(fc)
    cache.build_cache()
    srv = ExtenderServer(cache, fc, host="127.0.0.1", port=0)
    assert srv.nativewire.enabled
    # start from a quiet ring: no leftovers from a previous test
    native_engine.blackbox_disable()
    drain_raw()
    yield fc, cache, srv
    srv.nativewire.close()
    native_engine.blackbox_disable()
    nativewire.RING_LATENCY_ACTIVE = False
    drain_raw()


def serve_py(srv, body: bytes) -> bytes:
    status, payload, _ = srv.handle_post(FILTER_PATH, body)
    assert status == 200
    return payload


def prime(srv, body: bytes) -> bytes:
    """Two Python serves: the first installs, the second re-installs
    under the settled stamp (and registers the digest map entry)."""
    serve_py(srv, body)
    return serve_py(srv, body)


def armed_frame(srv, hbm: int = 1024) -> bytes:
    body = json.dumps({"Pod": make_pod(hbm=hbm),
                       "NodeNames": NAMES}).encode()
    prime(srv, body)
    return http_bytes(FILTER_PATH, body)


def test_ring_captures_probe_events_with_native_timing(rig):
    fc, cache, srv = rig
    raw = armed_frame(srv)
    native_engine.blackbox_enable()
    try:
        for _ in range(5):
            rc, _, _ = srv.nativewire.probe_request(bytearray(raw))
            assert rc == PROBE_HIT
        rows = drain_raw()
    finally:
        native_engine.blackbox_disable()
    hits = [r for r in rows if r[0] == bb.KIND_WIRE_PROBE
            and bb.decode_wire_outcome(r[1])[0] == 1]
    assert len(hits) == 5
    for _kind, outcome, t_ns, dur_ns, span8, rem8 in hits:
        rc, verb_id = bb.decode_wire_outcome(outcome)
        assert (rc, verb_id) == (1, 0)  # hit, filter
        assert t_ns > 0
        assert 0 < dur_ns < 1_000_000_000  # native µs-scale, not garbage
        assert (span8, rem8) != (0, 0)  # digest prefixes travelled


def test_ring_overflow_drops_counted_never_corrupted(rig):
    """5000 un-drained probes into a 4096-slot ring: the producer must
    drop and count, and everything that IS drained must still decode —
    and the pump must surface the loss as the dropped-total counter."""
    fc, cache, srv = rig
    raw = armed_frame(srv)
    native_engine.blackbox_enable()
    dropped0 = native_engine.blackbox_stats()["dropped_total"]
    metric0 = bb.BLACKBOX_DROPPED.value
    try:
        for _ in range(5000):
            rc, _, _ = srv.nativewire.probe_request(bytearray(raw))
            assert rc == PROBE_HIT  # drop-on-full never fails the serve
        ring_dropped = (native_engine.blackbox_stats()["dropped_total"]
                        - dropped0)
        assert ring_dropped > 0
        # the pump turns the cumulative ring count into metric deltas
        pumped = srv.blackbox.drain_once()
    finally:
        native_engine.blackbox_disable()
    assert 0 < pumped <= 4096
    assert bb.BLACKBOX_DROPPED.value - metric0 >= ring_dropped
    for kind, outcome, t_ns, dur_ns, _s8, _r8 in drain_raw():
        assert kind in bb.KINDS
        assert t_ns > 0 and dur_ns >= 0
        if kind == bb.KIND_WIRE_PROBE:
            rc, verb_id = bb.decode_wire_outcome(outcome)
            assert rc in bb.WIRE_OUTCOMES
            assert verb_id in (0, 1, 255)


def test_pump_attributes_native_latency_exactly_once(rig):
    """Satellite: with the pump active the histogram's samples are the
    ring's tick deltas — exactly one per probe, the serve path's
    perf_counter envelope suppressed (no double count)."""
    fc, cache, srv = rig
    raw = armed_frame(srv)
    hist0 = sum(nativewire.WIRE_NATIVE_PROBE_SECONDS.state()["counts"])
    native_engine.blackbox_enable()
    nativewire.RING_LATENCY_ACTIVE = True
    try:
        for _ in range(7):
            rc, _, _ = srv.nativewire.probe_request(bytearray(raw))
            assert rc == PROBE_HIT
        assert srv.blackbox.drain_once() == 7
    finally:
        nativewire.RING_LATENCY_ACTIVE = False
        native_engine.blackbox_disable()
    hist1 = sum(nativewire.WIRE_NATIVE_PROBE_SECONDS.state()["counts"])
    assert hist1 - hist0 == 7


def test_native_storm_leaves_zero_unexplained_pods(rig):
    """The regression this PR closes: a native-heavy storm over a real
    socket must leave EVERY pod with a truthful ``source: native``
    explain record (joined through the digest map), honest per-serve
    durations, and — with the pin threshold at zero — native serves in
    the flight recorder."""
    fc, cache, srv = rig
    port = srv.start()  # starts the ring pump alongside fleetwatch
    try:
        srv.tracer.recorder.slow_ms = 0.0  # pin every native serve
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        pods = [make_pod(hbm=256 * (i + 1), name=f"storm-{i}")
                for i in range(6)]
        for pod in pods:
            body = json.dumps({"Pod": pod, "NodeNames": NAMES}).encode()
            for _ in range(7):  # 2 python serves arm, then 5 native hits
                conn.request("POST", FILTER_PATH, body,
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                assert r.status == 200
                r.read()
        conn.close()
        assert srv.nativewire.stats()["hits"] >= 5 * len(pods)
        srv.blackbox.drain_once()  # deterministic: don't wait on period
        events0 = bb.BLACKBOX_EVENTS.get("wire_probe", "hit")
        assert events0 >= 5 * len(pods)
        for i in range(len(pods)):
            out = srv.explain.get(f"default/storm-{i}")
            assert out is not None, f"storm-{i} unexplained"
            native = [c["filter"] for c in out["cycles"]
                      if c.get("filter", {}).get("source") == "native"]
            assert native, f"storm-{i} has no source=native record"
            assert native[-1]["duration_ms"] is not None
            assert native[-1]["ok"] == len(NAMES)
        pinned = srv.tracer.recorder.pinned()
        assert any(getattr(t, "outcome", "") == "native_serve"
                   for t in pinned)
    finally:
        srv.stop()


def test_pump_stop_restores_python_side_latency(rig):
    fc, cache, srv = rig
    pump = srv.blackbox
    pump.start()
    assert nativewire.RING_LATENCY_ACTIVE
    pump.stop()
    assert not nativewire.RING_LATENCY_ACTIVE
    # after stop the serve path observes its own envelope again
    raw = armed_frame(srv)
    hist0 = sum(nativewire.WIRE_NATIVE_PROBE_SECONDS.state()["counts"])
    rc, _, _ = srv.nativewire.probe_request(bytearray(raw))
    assert rc == PROBE_HIT
    hist1 = sum(nativewire.WIRE_NATIVE_PROBE_SECONDS.state()["counts"])
    assert hist1 - hist0 == 1
