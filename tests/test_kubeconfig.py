"""Kubeconfig parsing tests (reference initKubeClient honors KUBECONFIG,
/root/reference/cmd/main.go:24-38)."""

import base64
import json
import os
import stat

import pytest
import yaml

from tests.test_contract import make_node
from tpushare.k8s.incluster import InClusterClient
from tpushare.k8s.kubeconfig import (
    KubeconfigError,
    load_kubeconfig,
)
from tpushare.k8s.stubapi import StubApiServer


def write_cfg(tmp_path, users, clusters=None, contexts=None, current="c1",
              name="config"):
    cfg = {
        "apiVersion": "v1", "kind": "Config",
        "current-context": current,
        "clusters": clusters or [
            {"name": "cl1", "cluster": {"server": "https://10.0.0.1:6443"}}],
        "contexts": contexts or [
            {"name": "c1", "context": {"cluster": "cl1", "user": "u1"}}],
        "users": users,
    }
    p = tmp_path / name
    p.write_text(yaml.safe_dump(cfg))
    return str(p)


def test_token_user(tmp_path):
    p = write_cfg(tmp_path, [{"name": "u1", "user": {"token": "tok-abc"}}])
    auth = load_kubeconfig(p)
    assert auth.server == "https://10.0.0.1:6443"
    assert auth.token == "tok-abc"
    assert auth.headers() == {"Authorization": "Bearer tok-abc"}
    assert auth.ssl_context is not None  # https => TLS configured


def test_token_file_relative_to_kubeconfig_dir(tmp_path):
    (tmp_path / "tok").write_text("from-file\n")
    p = write_cfg(tmp_path, [{"name": "u1", "user": {"tokenFile": "tok"}}])
    assert load_kubeconfig(p).token == "from-file"


def test_context_selection_and_missing_context(tmp_path):
    p = write_cfg(
        tmp_path,
        users=[{"name": "u1", "user": {"token": "t1"}},
               {"name": "u2", "user": {"token": "t2"}}],
        clusters=[
            {"name": "cl1", "cluster": {"server": "https://a:6443"}},
            {"name": "cl2", "cluster": {"server": "https://b:6443"}}],
        contexts=[
            {"name": "c1", "context": {"cluster": "cl1", "user": "u1"}},
            {"name": "c2", "context": {"cluster": "cl2", "user": "u2"}}])
    auth = load_kubeconfig(p, context="c2")
    assert auth.server == "https://b:6443" and auth.token == "t2"
    with pytest.raises(KubeconfigError):
        load_kubeconfig(p, context="ghost")


def test_inline_ca_and_client_cert_data(tmp_path):
    # self-signed cert+key so load_cert_chain has something real to parse
    pem_cert, pem_key = _selfsigned()
    users = [{"name": "u1", "user": {
        "client-certificate-data": base64.b64encode(pem_cert).decode(),
        "client-key-data": base64.b64encode(pem_key).decode()}}]
    clusters = [{"name": "cl1", "cluster": {
        "server": "https://10.0.0.1:6443",
        "certificate-authority-data": base64.b64encode(pem_cert).decode()}}]
    p = write_cfg(tmp_path, users, clusters=clusters)
    auth = load_kubeconfig(p)
    assert auth.token is None
    assert auth.ssl_context is not None
    assert auth.headers() == {}


def test_insecure_skip_tls_verify(tmp_path):
    clusters = [{"name": "cl1", "cluster": {
        "server": "https://10.0.0.1:6443",
        "insecure-skip-tls-verify": True}}]
    p = write_cfg(tmp_path, [{"name": "u1", "user": {"token": "t"}}],
                  clusters=clusters)
    ctx = load_kubeconfig(p).ssl_context
    import ssl
    assert ctx.verify_mode == ssl.CERT_NONE and not ctx.check_hostname


def test_exec_credential_plugin(tmp_path):
    helper = tmp_path / "helper.sh"
    helper.write_text(
        "#!/bin/sh\n"
        'echo \'{"apiVersion":"client.authentication.k8s.io/v1",'
        '"kind":"ExecCredential","status":{"token":"exec-tok"}}\'\n')
    helper.chmod(helper.stat().st_mode | stat.S_IEXEC)
    users = [{"name": "u1", "user": {"exec": {
        "apiVersion": "client.authentication.k8s.io/v1",
        "command": str(helper), "args": [], "env": []}}}]
    p = write_cfg(tmp_path, users)
    assert load_kubeconfig(p).token == "exec-tok"


def test_exec_plugin_failure_raises(tmp_path):
    users = [{"name": "u1", "user": {"exec": {
        "command": "/nonexistent-helper-xyz"}}}]
    p = write_cfg(tmp_path, users)
    with pytest.raises(KubeconfigError):
        load_kubeconfig(p)


def test_basic_auth_user(tmp_path):
    p = write_cfg(tmp_path, [{"name": "u1", "user": {
        "username": "admin", "password": "pw"}}])
    auth = load_kubeconfig(p)
    expected = base64.b64encode(b"admin:pw").decode()
    assert auth.headers() == {"Authorization": f"Basic {expected}"}


def test_kubeconfig_env_fallback(tmp_path, monkeypatch):
    p = write_cfg(tmp_path, [{"name": "u1", "user": {"token": "env-tok"}}])
    monkeypatch.setenv("KUBECONFIG", p)
    assert load_kubeconfig().token == "env-tok"
    monkeypatch.setenv("KUBECONFIG", str(tmp_path / "missing"))
    with pytest.raises(KubeconfigError):
        load_kubeconfig()


def test_client_from_kubeconfig_against_stub(tmp_path, monkeypatch):
    """End to end: a kubeconfig-built client authenticates to the stub
    apiserver with its bearer token."""
    stub = StubApiServer(token="kc-tok").start()
    try:
        clusters = [{"name": "cl1", "cluster": {"server": stub.base_url}}]
        p = write_cfg(tmp_path, [{"name": "u1", "user": {"token": "kc-tok"}}],
                      clusters=clusters)
        monkeypatch.setenv("KUBECONFIG", p)
        client = InClusterClient.autodetect()
        stub.seed("nodes", make_node("n1"))
        assert client.get_node("n1")["metadata"]["name"] == "n1"
    finally:
        stub.stop()


def _selfsigned():
    """Generate a throwaway self-signed cert+key PEM pair via openssl if
    available, else skip."""
    import subprocess
    import tempfile
    d = tempfile.mkdtemp()
    cert, key = os.path.join(d, "c.pem"), os.path.join(d, "k.pem")
    try:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", cert, "-days", "1", "-subj",
             "/CN=test"], capture_output=True, check=True, timeout=60)
    except (OSError, subprocess.SubprocessError):
        pytest.skip("openssl unavailable for self-signed cert generation")
    with open(cert, "rb") as f:
        pem_cert = f.read()
    with open(key, "rb") as f:
        pem_key = f.read()
    return pem_cert, pem_key
