"""Native C++ engine parity: placement.cpp must match select_chips_py.

Randomized differential test over fleets of node states. Skipped when the
shared object cannot be built (no g++).
"""

import random

import pytest

from tpushare.core.chips import ChipView
from tpushare.core.native import engine as native_engine
from tpushare.core.placement import PlacementRequest, select_chips_py
from tpushare.core.topology import MeshTopology

pytestmark = pytest.mark.skipif(
    not native_engine.available(), reason="native engine unavailable")


def random_case(rng):
    n = rng.choice([1, 2, 4, 8, 16])
    shape = MeshTopology.for_chip_count(n).shape
    topo = MeshTopology(shape)
    total = rng.choice([8192, 16276])
    chips = [
        ChipView(i, topo.coords(i), total, rng.randrange(0, total + 1),
                 healthy=rng.random() > 0.15)
        for i in range(n)
    ]
    count = rng.choice([1, 1, 2, 4])
    topology = None
    if count > 1 and rng.random() < 0.4:
        choices = list(topo.box_shapes(count))
        # rank-mismatched pin exercises the drop-to-scatter path
        if len(topo.shape) > 1:
            choices.append((count,))
        if choices:
            topology = rng.choice(choices)
    req = PlacementRequest(
        hbm_mib=rng.choice([0, 512, 2048, 8138]),
        chip_count=count,
        topology=topology,
        allow_scatter=rng.random() < 0.5,
    )
    # input order must not affect the decision in either engine
    rng.shuffle(chips)
    return chips, topo, req


def test_differential_vs_python():
    rng = random.Random(7)
    for trial in range(500):
        chips, topo, req = random_case(rng)
        py = select_chips_py(chips, topo, req)
        nat = native_engine.select_chips(chips, topo, req)
        if py is None:
            assert nat is None, (trial, req, chips)
        else:
            assert nat is not None, (trial, req, chips)
            assert nat.chip_ids == py.chip_ids, (trial, req, chips)
            assert nat.box == py.box, (trial, req)
            assert nat.score == py.score, (trial, req)


def test_fits_fleet_parity():
    """The one-call fleet Filter must agree with per-node fits()."""
    from tpushare.core.placement import fits as fits_py

    rng = random.Random(99)
    for trial in range(60):
        nodes = []
        for _ in range(rng.randrange(1, 12)):
            chips, topo, _ = random_case(rng)
            nodes.append((chips, topo))
        _, _, req = random_case(rng)
        fleet = native_engine.fits_fleet(nodes, req)
        per_node = [fits_py(chips, topo, req) for chips, topo in nodes]
        assert fleet == per_node, (trial, req)


def test_score_fleet_parity():
    """The one-call fleet Prioritize must agree with per-node
    select_chips_py scores (None where no placement exists)."""
    rng = random.Random(41)
    for trial in range(60):
        nodes = []
        for _ in range(rng.randrange(1, 12)):
            chips, topo, _ = random_case(rng)
            nodes.append((chips, topo))
        _, _, req = random_case(rng)
        fleet = native_engine.score_fleet(nodes, req)
        per_node = []
        for chips, topo in nodes:
            p = select_chips_py(chips, topo, req)
            per_node.append(None if p is None else p.score)
        assert fleet == per_node, (trial, req)


def test_fits_fleet_handles_gappy_ids():
    # a node with non-dense chip ids must fall back to the Python path
    from tpushare.core.placement import fits as fits_py

    topo = MeshTopology((2, 2))
    gappy = [ChipView(i, topo.coords(min(i, 3)), 16000, 0)
             for i in (0, 1, 2, 4)]
    dense = [ChipView(i, topo.coords(i), 16000, 0) for i in range(4)]
    req = PlacementRequest(hbm_mib=1000, chip_count=4)
    fleet = native_engine.fits_fleet([(gappy, topo), (dense, topo)], req)
    assert fleet == [fits_py(gappy, topo, req), True]


def test_fits_fleet_out_of_order_chip_snapshot():
    # dense but unsorted chip list, delivered as a weakref-able
    # ChipSnapshot (the caching key type): must pack correctly, not crash
    from tpushare.core.chips import ChipSnapshot
    from tpushare.core.placement import fits as fits_py

    topo = MeshTopology((2, 2))
    shuffled = ChipSnapshot(
        ChipView(i, topo.coords(i), 16000, 0) for i in (2, 0, 3, 1))
    req = PlacementRequest(hbm_mib=1000, chip_count=4)
    for _ in range(2):  # second call exercises the cached-pack path
        fleet = native_engine.fits_fleet([(shuffled, topo)], req)
        assert fleet == [fits_py(shuffled, topo, req)] == [True]


def test_topology_pin_parity():
    topo = MeshTopology((4, 4))
    chips = [ChipView(i, topo.coords(i), 16000, 0) for i in range(16)]
    req = PlacementRequest(hbm_mib=1000, chip_count=4, topology=(2, 2))
    py = select_chips_py(chips, topo, req)
    nat = native_engine.select_chips(chips, topo, req)
    assert py.chip_ids == nat.chip_ids and py.box == nat.box


# -- gang selector parity (placement.cpp tpushare_select_gang) --------------

def _random_slice_case(rng):
    from tpushare.core.slice import SliceTopology

    grid, box = rng.choice([((2, 2), (2, 2)), ((1, 2), (2, 2)),
                            ((2, 1), (1, 4)), ((2, 2, 1), (1, 2, 2))])
    n_hosts = 1
    for d in grid:
        n_hosts *= d
    names = [f"h{i}" for i in range(n_hosts)]
    st = SliceTopology.from_host_grid(grid, box, names)
    local = MeshTopology(box)
    total = 16000
    views = {}
    for h in names:
        if rng.random() < 0.1:
            continue  # missing host snapshot
        views[h] = [
            ChipView(i, local.coords(i), total,
                     rng.choice([0, 0, 4000, 12000, total]),
                     healthy=rng.random() > 0.1)
            for i in range(local.num_chips)
        ]
    count = rng.choice([2, 4, 4, 8])
    topology = None
    if rng.random() < 0.4:
        shapes = [s for s in st.mesh.box_shapes(count)
                  if len(s) == len(st.mesh.shape)]
        if shapes:
            topology = rng.choice(shapes)
    req = PlacementRequest(hbm_mib=rng.choice([0, 4000, 8000]),
                           chip_count=count, topology=topology)
    return st, views, req


def test_select_gang_parity_native_vs_python():
    from tpushare.core import slice as slice_mod
    from tpushare.core.native import engine

    rng = random.Random(99)
    checked = native_hits = 0
    for _ in range(150):
        st, views, req = _random_slice_case(rng)
        via_native = engine.select_gang_box(st, views, req)
        py = slice_mod._search_gang(st, views, req, first_only=False)
        if via_native == "fallback":
            continue
        native_hits += 1
        if py is None:
            assert via_native is None, (req, views)
            continue
        assert via_native is not None, (req, views)
        box, origin = via_native
        # full policy key must match: shape class, hosts, score, origin
        assert box == py.box and origin == py.origin, (
            req, box, origin, py)
        # and the assembled GangPlacement through the dispatching
        # frontend equals the pure-Python one entirely
        gp = slice_mod.select_gang(st, views, req)
        assert gp == py
        checked += 1
    assert native_hits > 100  # the native path actually ran
    assert checked > 20  # ...and the deep-equality leg actually ran too


# -- ABI v5 one-shot gang solve (tpushare_solve_gang) ----------------------


def random_gang_case(rng):
    """A random multi-host slice (2-d grids, mixed host boxes), random
    per-chip occupancy/health, and a random gang request."""
    from tpushare.core.slice import SliceTopology
    from tpushare.core.topology import HostMesh

    grid = rng.choice([(1, 2), (2, 2), (2, 4), (4, 2), (2, 3), (3, 3)])
    hbox = rng.choice([(2, 2), (1, 2), (2, 1)])
    n_hosts = grid[0] * grid[1]
    names = [f"h{i}" for i in range(n_hosts)]
    st = SliceTopology.from_host_grid(grid, hbox, names)
    hmesh = HostMesh(grid, hbox, tuple(names))
    total = rng.choice([8192, 16384])
    views = {}
    for name in names:
        local = st.local_topology(name)
        views[name] = [
            ChipView(i, local.coords(i), total,
                     rng.choice([0, 0, 512, total // 2, total]),
                     healthy=rng.random() > 0.1)
            for i in range(local.num_chips)
        ]
    if n_hosts > 2 and rng.random() < 0.2:
        # absent host (down, unreported): boxes touching it must be
        # ineligible in BOTH engines — the degraded-fleet contract
        del views[rng.choice(names)]
    mesh_chips = st.mesh.num_chips
    count = rng.choice([c for c in (2, 4, 8, 16) if c <= mesh_chips])
    topology = None
    if rng.random() < 0.5:
        shapes = st.mesh.box_shapes(count)
        if shapes:
            topology = rng.choice(shapes)
    req = PlacementRequest(
        hbm_mib=rng.choice([0, 0, 512, 2048, total // 2]),
        chip_count=count, topology=topology, allow_scatter=False)
    return st, hmesh, views, req


@pytest.mark.skipif(not native_engine.gang_solve_supported(),
                    reason="solve_gang entry point unavailable")
def test_solve_gang_differential_vs_python_spec():
    """engine.solve_gang (ABI v5 one-shot: C search + in-C member
    decomposition off a resident arena) must match _py_solve_gang (the
    pure-python behavioral spec) on randomized fleets — box, origin,
    score, AND every member's local chip ids/box/origin."""
    from tpushare.core.slice import _py_solve_gang

    rng = random.Random(41)
    native_hits = placed = 0
    for trial in range(400):
        st, hmesh, views, req = random_gang_case(rng)
        py = _py_solve_gang(st, views, req)
        nat = native_engine.solve_gang(st, hmesh, views, req)
        assert nat != "fallback", "supported build must not fall back"
        native_hits += 1
        if py is None:
            assert nat is None, (trial, req)
            continue
        placed += 1
        assert nat is not None, (trial, req)
        assert nat.box == py.box, (trial, req)
        assert nat.origin == py.origin, (trial, req)
        assert nat.score == py.score, (trial, req)
        assert sorted(nat.per_host) == sorted(py.per_host), (trial, req)
        for host, pp in py.per_host.items():
            np_ = nat.per_host[host]
            assert np_.chip_ids == pp.chip_ids, (trial, host, req)
            assert np_.box == pp.box, (trial, host, req)
            assert np_.origin == pp.origin, (trial, host, req)
    # the sweep must actually exercise both engines and real placements
    assert native_hits == 400
    assert placed > 50, f"only {placed} placements — weak sweep"


@pytest.mark.skipif(not native_engine.gang_solve_supported(),
                    reason="solve_gang entry point unavailable")
def test_solve_gang_resident_arena_incremental_sync_parity():
    """A RESIDENT arena synced incrementally (stamp-hit hosts skipped,
    moved hosts resynced, one host promised-unchanged-but-moved) must
    answer exactly like a fresh full solve of the same state."""
    from tpushare.core.native.engine import SliceArena
    from tpushare.core.slice import SliceTopology, _py_solve_gang
    from tpushare.core.topology import HostMesh

    rng = random.Random(43)
    grid, hbox = (2, 4), (2, 2)
    names = [f"h{i}" for i in range(8)]
    st = SliceTopology.from_host_grid(grid, hbox, names)
    hmesh = HostMesh(grid, hbox, tuple(names))
    total = 16384

    def fresh_views(used):
        return {n: [ChipView(i, st.local_topology(n).coords(i), total,
                             used[n][i]) for i in range(4)]
                for n in names}

    used = {n: [0] * 4 for n in names}
    arena = SliceArena(st, hmesh)
    arena.sync({n: ((1, i), fresh_views(used)[n])
                for i, n in enumerate(names)})
    req = PlacementRequest(hbm_mib=2048, chip_count=8, topology=(2, 4),
                           allow_scatter=False)
    for step in range(60):
        # mutate a couple of hosts; the rest sync by stamp alone
        moved = rng.sample(names, rng.randint(0, 2))
        for n in moved:
            used[n][rng.randrange(4)] = rng.choice([0, 512, total])
        views = fresh_views(used)
        sync_map = {}
        for i, n in enumerate(names):
            stamp = (2 + step, i) if n in moved else arena.stamp(n)
            sync_map[n] = (stamp, views[n] if n in moved else None)
        arena.sync(sync_map)
        got = arena.solve(req)
        want = _py_solve_gang(st, views, req)
        if want is None:
            assert got is None, step
            continue
        assert got is not None and got != "fallback", step
        assert got.box == want.box and got.origin == want.origin, step
        assert {h: p.chip_ids for h, p in got.per_host.items()} == \
            {h: p.chip_ids for h, p in want.per_host.items()}, step


@pytest.mark.skipif(not native_engine.gang_solve_supported(),
                    reason="solve_gang entry point unavailable")
def test_slice_arena_sync_unit_semantics():
    """The delta-sync contract, host by host: stamp-hit hosts cost no
    rewrite, a promised-unchanged host whose stamp moved anyway goes
    ineligible (never solved stale), and a host absent from the
    mapping goes ineligible — the degraded global_view semantics."""
    from tpushare.core.native.engine import SliceArena
    from tpushare.core.slice import SliceTopology
    from tpushare.core.topology import HostMesh

    grid, hbox = (1, 2), (2, 2)
    names = ["h0", "h1"]
    st = SliceTopology.from_host_grid(grid, hbox, names)
    hmesh = HostMesh(grid, hbox, tuple(names))
    total = 16384

    def views(name):
        lt = st.local_topology(name)
        return [ChipView(i, lt.coords(i), total, 0) for i in range(4)]

    arena = SliceArena(st, hmesh)
    assert arena.stamp("h0") is None  # never synced
    arena.sync({n: ((1, 0), views(n)) for n in names})
    assert arena.stamp("h0") == (1, 0)
    assert arena.host_updates == 2
    req8 = PlacementRequest(hbm_mib=0, chip_count=8, topology=(2, 4),
                            allow_scatter=False)
    gp = arena.solve(req8)
    assert gp is not None and gp != "fallback"
    assert set(gp.per_host) == {"h0", "h1"}

    # stamp-hit skip: promised-unchanged hosts cost zero rewrites
    arena.sync({n: ((1, 0), None) for n in names})
    assert arena.host_updates == 2
    assert arena.solve(req8) is not None

    # promised-unchanged host whose stamp MOVED: the caller skipped the
    # snapshot, so the arena must refuse to solve that host stale
    arena.sync({"h0": ((1, 7), None), "h1": ((1, 0), None)})
    assert arena.stamp("h0") is None
    assert arena.solve(req8) is None  # h0 ineligible: no 2x4 box
    req4 = PlacementRequest(hbm_mib=0, chip_count=4, topology=(2, 2),
                            allow_scatter=False)
    gp4 = arena.solve(req4)
    assert gp4 is not None and set(gp4.per_host) == {"h1"}

    # a real resync with fresh chips brings the host back
    arena.sync({"h0": ((1, 8), views("h0")), "h1": ((1, 0), None)})
    assert arena.solve(req8) is not None

    # absent host (down/unreported): ineligible until it reappears
    arena.sync({"h0": ((1, 8), None)})
    assert arena.stamp("h1") is None
    assert arena.solve(req8) is None
    gp4b = arena.solve(req4)
    assert gp4b is not None and set(gp4b.per_host) == {"h0"}
