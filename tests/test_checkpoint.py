"""Checkpoint/resume for the training workload (workloads/checkpoint.py).

The scenario under test is the control plane's preempt verb seen from the
workload side: a gang member is killed mid-run, re-placed (possibly onto a
different slice shape), and must continue from the latest durable step —
bitwise, on a different mesh, and never from a half-written checkpoint.
The reference has no trainer, so there is no reference behavior to match;
the contract here is the module's own.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpushare.workloads.checkpoint import (
    TrainCheckpointer, abstract_train_state, make_resumable_trainer,
    opt_specs_like)
from tpushare.workloads.model import PRESETS, make_train_step

CFG = PRESETS["llama-tiny"]
TOKENS = jnp.arange(4 * 32, dtype=jnp.int32).reshape(4, 32) % CFG.vocab


def mesh(dp, tp):
    return Mesh(np.array(jax.devices()).reshape(dp, tp), ("dp", "tp"))


def leaves_equal(a, b):
    la = jax.tree.leaves(a)
    lb = jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def train_n(train_step, params, opt, n):
    step_jit = jax.jit(train_step)
    for _ in range(n):
        params, opt, _ = step_jit(params, opt, TOKENS)
    return params, opt


def test_resume_is_bitwise_identical_to_uninterrupted_run(tmp_path):
    # 5 straight steps == 3 steps + save + restore + 2 steps: the
    # checkpoint carries ALL state that affects the trajectory (params
    # AND adamw moments — dropping opt_state would pass a looser test)
    ckpt, tx, train_step = make_resumable_trainer(CFG, str(tmp_path))
    params, opt, start = ckpt.resume_or_init(CFG, tx, jax.random.key(0))
    assert start == 0
    straight_p, _ = train_n(train_step, params, opt, 5)

    p3, o3 = train_n(train_step, params, opt, 3)
    ckpt.save(3, p3, o3, CFG)
    rp, ro, rstep = ckpt.restore(CFG, tx)
    assert rstep == 3
    resumed_p, _ = train_n(train_step, rp, ro, 2)
    leaves_equal(straight_p, resumed_p)
    ckpt.close()


def test_cross_mesh_restore_reshards_params_and_opt_state(tmp_path):
    # saved under dp=2 x tp=4, restored under dp=4 x tp=2 — the
    # re-placement-onto-a-different-slice-shape case. Values must be
    # identical and the restored arrays must CARRY the target sharding
    # (orbax reads shards straight onto the new layout; no host gather).
    tx, train_step = make_train_step(CFG)
    with TrainCheckpointer(str(tmp_path)) as ckpt:
        params, opt, _ = ckpt.resume_or_init(
            CFG, tx, jax.random.key(0), mesh=mesh(2, 4))
        params, opt = train_n(train_step, params, opt, 2)
        ckpt.save(2, params, opt, CFG)

        m42 = mesh(4, 2)
        rp, ro, _ = ckpt.restore(CFG, tx, mesh=m42)
        leaves_equal(params, rp)
        leaves_equal(opt, ro)
        wq = rp["layers"]["wq"]
        assert wq.sharding.spec == P(None, None, "tp")
        assert dict(wq.sharding.mesh.shape) == {"dp": 4, "tp": 2}
        # adamw first moment is sharded like its param, on the new mesh
        mu_wq = ro[0].mu["layers"]["wq"]
        assert mu_wq.sharding.spec == P(None, None, "tp")
        assert dict(mu_wq.sharding.mesh.shape) == {"dp": 4, "tp": 2}
        # training continues on the new mesh
        _, _, loss = jax.jit(train_step)(rp, ro, TOKENS)
        assert bool(jnp.isfinite(loss))


def test_geometry_mismatch_refuses_restore(tmp_path):
    tx, _ = make_train_step(CFG)
    with TrainCheckpointer(str(tmp_path)) as ckpt:
        params, opt, _ = ckpt.resume_or_init(CFG, tx, jax.random.key(0))
        ckpt.save(1, params, opt, CFG)
        wider = dataclasses.replace(CFG, d_model=128, n_heads=8,
                                    n_kv_heads=4)
        tx2, _ = make_train_step(wider)
        with pytest.raises(ValueError, match="geometry"):
            ckpt.restore(wider, tx2)
        # the guard must fire on the MESH path too (the one player.py
        # uses) — i.e. BEFORE StandardRestore's strict shape check,
        # whose error names a tensor instead of the mistake
        with pytest.raises(ValueError, match="geometry"):
            ckpt.restore(wider, tx2, mesh=mesh(2, 4))


def test_retention_keeps_newest_n(tmp_path):
    tx, _ = make_train_step(CFG)
    with TrainCheckpointer(str(tmp_path), keep=2) as ckpt:
        params, opt, _ = ckpt.resume_or_init(CFG, tx, jax.random.key(0))
        for step in (1, 2, 3):
            ckpt.save(step, params, opt, CFG)
        assert ckpt.latest_step() == 3
        assert ckpt.steps() == [2, 3]


def test_resume_or_init_discovers_prior_process_state(tmp_path):
    # two manager instances = two process lifetimes: the second one finds
    # the first one's save (the actual preempt/re-place sequence)
    tx, train_step = make_train_step(CFG)
    with TrainCheckpointer(str(tmp_path)) as ckpt:
        params, opt, start = ckpt.resume_or_init(CFG, tx,
                                                 jax.random.key(0))
        assert start == 0
        params, opt = train_n(train_step, params, opt, 2)
        ckpt.save(2, params, opt, CFG)

    with TrainCheckpointer(str(tmp_path)) as ckpt2:
        rp, ro, start = ckpt2.resume_or_init(CFG, tx, jax.random.key(0))
        assert start == 2
        leaves_equal(params, rp)


def test_vit_family_checkpoint_cross_mesh(tmp_path):
    # the checkpointer dispatches by config type: the ViT family gets
    # the same cross-mesh restore + geometry guard as llama, and a
    # llama config can never load a vit checkpoint (family recorded in
    # the geometry meta)
    from tpushare.workloads.vit import PRESETS_VIT
    vcfg = PRESETS_VIT["vit-tiny"]
    ckpt, tx, train_step = make_resumable_trainer(vcfg, str(tmp_path))
    params, opt, start = ckpt.resume_or_init(vcfg, tx, jax.random.key(0),
                                             mesh=mesh(2, 4))
    assert start == 0
    images = jnp.zeros((2, 32, 32, 3), jnp.float32)
    labels = jnp.array([1, 2], jnp.int32)
    step_jit = jax.jit(train_step)
    for _ in range(2):
        params, opt, _ = step_jit(params, opt, images, labels)
    ckpt.save(2, params, opt, vcfg)

    rp, ro, rstep = ckpt.restore(vcfg, tx, mesh=mesh(4, 2))
    assert rstep == 2
    leaves_equal(params, rp)
    wq = rp["layers"]["wq"]
    assert wq.sharding.spec == P(None, None, "tp")
    assert dict(wq.sharding.mesh.shape) == {"dp": 4, "tp": 2}

    # cross-family restore refused via the geometry meta
    ltx, _ = make_train_step(CFG)
    with pytest.raises(ValueError, match="geometry"):
        ckpt.restore(CFG, ltx)
    ckpt.close()


def test_unknown_config_type_fails_loudly(tmp_path):
    class WeirdConfig:
        pass

    with TrainCheckpointer(str(tmp_path)) as ckpt:
        tx, _ = make_train_step(CFG)
        with pytest.raises(TypeError, match="unknown workload family"):
            ckpt.save(1, {}, {}, WeirdConfig())


def test_pre_family_tag_checkpoint_still_restores(tmp_path):
    # checkpoints written before the family tag existed carry no
    # 'family' key; an upgrade mid-run must not strand a preempted
    # trainer's own valid checkpoint
    import glob
    import json as _json
    tx, _ = make_train_step(CFG)
    with TrainCheckpointer(str(tmp_path)) as ckpt:
        params, opt, _ = ckpt.resume_or_init(CFG, tx, jax.random.key(0))
        ckpt.save(1, params, opt, CFG)
    meta_files = glob.glob(str(tmp_path) + "/**/metadata", recursive=True)
    stripped = 0
    for f in glob.glob(str(tmp_path) + "/**/*", recursive=True):
        try:
            with open(f) as fh:
                doc = _json.load(fh)
        except (IsADirectoryError, UnicodeDecodeError, ValueError,
                PermissionError):
            continue
        if isinstance(doc, dict) and doc.get("family") == "llama":
            del doc["family"]
            with open(f, "w") as fh:
                _json.dump(doc, fh)
            stripped += 1
    assert stripped, f"no meta JSON found to strip (saw {meta_files})"
    with TrainCheckpointer(str(tmp_path)) as ckpt2:
        rp, _, step = ckpt2.restore(CFG, tx)
        assert step == 1
        leaves_equal(params, rp)


def test_opt_specs_mirror_param_specs():
    tx, _ = make_train_step(CFG)
    abstract = abstract_train_state(CFG, tx)
    specs = opt_specs_like(CFG, abstract["opt_state"])
    flat = {tuple(str(getattr(e, "key", getattr(e, "name", e)))
                  for e in path): spec
            for path, spec in jax.tree_util.tree_flatten_with_path(
                specs, is_leaf=lambda x: isinstance(x, P))[0]}
    mu_wq = [v for k, v in flat.items()
             if k[-2:] == ("layers", "wq") and "mu" in str(k)]
    assert mu_wq and all(s == P(None, None, "tp") for s in mu_wq)
    counts = [v for k, v in flat.items() if "count" in str(k[-1])]
    assert counts and all(s == P() for s in counts)


def test_abstract_state_carries_target_shardings():
    tx, _ = make_train_step(CFG)
    m = mesh(2, 4)
    abstract = abstract_train_state(CFG, tx, mesh=m)
    wq = abstract["params"]["layers"]["wq"]
    assert isinstance(wq.sharding, NamedSharding)
    assert wq.sharding.spec == P(None, None, "tp")
    nu_wq = abstract["opt_state"][0].nu["layers"]["wq"]
    assert nu_wq.sharding.spec == P(None, None, "tp")


def test_player_train_mode_resumes(tmp_path, capsys):
    # --steps is a TOTAL budget: the resumed run finishes the remainder
    # (2 done + --steps 3 => exactly 1 more step), so a re-placed gang
    # member with unchanged args never re-runs its whole budget
    from tpushare.workloads.player import main
    base = ["--preset", "llama-tiny", "--mode", "train", "--batch", "2",
            "--seq", "16", "--ckpt-dir", str(tmp_path),
            "--ckpt-every", "1"]
    assert main(base + ["--steps", "2"]) == 0
    capsys.readouterr()
    assert main(base + ["--steps", "3"]) == 0
    out = capsys.readouterr().out
    assert "resumed from step 2" in out
    with TrainCheckpointer(str(tmp_path)) as ckpt:
        assert ckpt.latest_step() == 3
        # the player built a ("dp","tp") mesh for the save: the state on
        # disk is the GLOBAL sharded pytree (multihost-coherent), and a
        # plain meshless restore still reads it fine
        tx, _ = make_train_step(CFG)
        _, _, step = ckpt.restore(CFG, tx)
        assert step == 3


def test_player_resumed_budget_already_spent_runs_zero_steps(tmp_path,
                                                             capsys):
    from tpushare.workloads.player import main
    base = ["--preset", "llama-tiny", "--mode", "train", "--batch", "2",
            "--seq", "16", "--ckpt-dir", str(tmp_path),
            "--ckpt-every", "1"]
    assert main(base + ["--steps", "2"]) == 0
    capsys.readouterr()
    assert main(base + ["--steps", "2"]) == 0
    assert "resumed from step 2" in capsys.readouterr().out
    with TrainCheckpointer(str(tmp_path)) as ckpt:
        assert ckpt.latest_step() == 2  # nothing re-run


def test_player_vit_train_mode_resumes(tmp_path, capsys):
    # the vit family rides the same player train wiring: preset name
    # selects the family, checkpoint/resume dispatches via _family
    from tpushare.workloads.player import main
    base = ["--preset", "vit-tiny", "--mode", "train", "--batch", "2",
            "--ckpt-dir", str(tmp_path), "--ckpt-every", "1"]
    assert main(base + ["--steps", "2"]) == 0
    capsys.readouterr()
    assert main(base + ["--steps", "3"]) == 0
    assert "resumed from step 2" in capsys.readouterr().out
    with TrainCheckpointer(str(tmp_path)) as ckpt:
        assert ckpt.latest_step() == 3


def test_player_refuses_moe_checkpoint_wiring(tmp_path):
    from tpushare.workloads.player import main
    with pytest.raises(SystemExit, match="dense"):
        main(["--preset", "llama-moe-tiny", "--mode", "train",
              "--steps", "1", "--ckpt-dir", str(tmp_path)])
