"""End-to-end continuous-batching serving (serve.py --engine).

Drives the real CLI surface in a subprocess — HTTP wire, engine thread,
slot admission — not the library. The subprocess is forced hermetic:
unsetting PALLAS_AXON_POOL_IPS disables the rig's TPU sitecustomize
registration, and JAX_PLATFORMS=cpu then selects the CPU backend
normally (conftest.py can't reach into a child process).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from tpushare.workloads.engine import DecodeEngine
from tpushare.workloads.model import PRESETS, init_params

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MAX_LEN = 64


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(port, body, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def serve_proc():
    port = _free_port()
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU registration
    p = subprocess.Popen(
        [sys.executable, "-m", "tpushare.workloads.serve",
         "--preset", "llama-tiny", "--quant", "none", "--engine",
         "--engine-slots", "4", "--engine-max-len", str(MAX_LEN),
         "--engine-quantum", "2", "--per-request-sampling",
         "--port", str(port)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + 90
    last = ""
    while time.time() < deadline:
        if p.poll() is not None:
            pytest.fail(f"serve exited rc={p.returncode}: "
                        f"{p.stdout.read()[-2000:]}")
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=2) as r:
                if r.status == 200:
                    break
        except OSError as e:
            last = str(e)
            time.sleep(0.5)
    else:
        pytest.fail(f"serve never became healthy: {last}")
    yield port
    p.send_signal(signal.SIGINT)
    try:
        p.wait(20)
    except subprocess.TimeoutExpired:
        p.kill()  # CPU-only child: no TPU claim to wedge


def _expected(prompts, steps):
    """The engine's own numerics in-process (same seed, same geometry —
    CPU either side), giving the wire test a bitwise target."""
    cfg = PRESETS["llama-tiny"].validate()
    params = init_params(cfg, jax.random.key(0))
    eng = DecodeEngine(params, cfg, max_slots=4, max_len=MAX_LEN,
                       quantum=2)
    rids = [eng.submit(list(map(int, p)), steps) for p in prompts]
    done = eng.drain()
    return [list(p) + done[r] for p, r in zip(prompts, rids)]


def test_single_and_batch_generation(serve_proc):
    port = serve_proc
    # single flat prompt: accepted, answered with prompt + steps tokens
    out = _post(port, {"tokens": [7, 3, 9], "steps": 4})["tokens"]
    assert len(out) == 1 and len(out[0]) == 3 + 4
    assert out[0][:3] == [7, 3, 9]
    assert out == _expected([[7, 3, 9]], 4)

    # ragged batch in one POST: all prompts co-resident, each row equals
    # its solo decode (continuous batching must not cross-pollute)
    prompts = [[5, 9], [100, 2, 77, 31], [240] * 7]
    rows = _post(port, {"tokens": prompts, "steps": 3})["tokens"]
    assert rows == _expected(prompts, 3)


def test_deterministic_across_requests(serve_proc):
    port = serve_proc
    a = _post(port, {"tokens": [12, 8, 4], "steps": 5})
    b = _post(port, {"tokens": [12, 8, 4], "steps": 5})
    assert a == b


def test_streaming_ndjson(serve_proc):
    port = serve_proc
    prompt = [7, 3, 9]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps({"tokens": prompt, "steps": 6,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    deltas, done_line = [], None
    with urllib.request.urlopen(req, timeout=120) as r:
        assert r.headers["Content-Type"] == "application/x-ndjson"
        for raw in r:
            ev = json.loads(raw)
            if "delta" in ev:
                assert done_line is None, "delta after done"
                deltas.append(ev["delta"])
            else:
                done_line = ev
    # >1 delta event = tokens actually arrived incrementally (quantum 2,
    # 6 tokens => prefill + >=2 quanta), and the stream reassembles to
    # exactly the non-streamed result
    assert len(deltas) >= 3
    flat = [t for d in deltas for t in d]
    assert done_line["done"] is True
    assert done_line["tokens"] == prompt + flat
    assert done_line["tokens"] == _expected([prompt], 6)[0]


def test_streaming_rejects_batch(serve_proc):
    port = serve_proc
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, {"tokens": [[1, 2], [3, 4]], "steps": 2,
                     "stream": True})
    assert ei.value.code == 400


def test_streaming_invalid_request_gets_400_not_200_body(serve_proc):
    # the status line is deferred until the first stream event, so a
    # submit-time rejection keeps the non-streaming path's 400 contract
    port = serve_proc
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, {"tokens": [1] * (MAX_LEN - 2), "steps": 10,
                     "stream": True})
    assert ei.value.code == 400


def test_eos_id_works_on_static_mode_replica():
    # the userguide's claim: eos_id needs NO --per-request-sampling
    # (the stop compare is per-slot state, not compiled structure);
    # guard the wire path on a default static-mode engine replica
    port = _free_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    p = subprocess.Popen(
        [sys.executable, "-m", "tpushare.workloads.serve",
         "--preset", "llama-tiny", "--quant", "none", "--engine",
         "--engine-slots", "2", "--engine-max-len", "32",
         "--port", str(port)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 90
        while time.time() < deadline:
            if p.poll() is not None:
                pytest.fail(f"serve exited rc={p.returncode}")
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz",
                        timeout=2) as r:
                    if r.status == 200:
                        break
            except OSError:
                time.sleep(0.5)
        full = _post(port, {"tokens": [7, 3], "steps": 4})["tokens"][0]
        stopped = _post(port, {"tokens": [7, 3], "steps": 4,
                               "eos_id": full[2]})["tokens"][0]
        assert stopped == full[:3]     # first generated token is eos
        # sampling overrides DO need the opt-in on this replica
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"tokens": [1, 2], "steps": 2,
                         "temperature": 1.0})
        assert ei.value.code == 400
    finally:
        p.send_signal(signal.SIGINT)
        try:
            p.wait(20)
        except subprocess.TimeoutExpired:
            p.kill()


def test_stream_without_engine_is_rejected():
    # a non-engine replica must refuse "stream": true loudly, not fall
    # through to a buffered json response the client will misparse
    port = _free_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    p = subprocess.Popen(
        [sys.executable, "-m", "tpushare.workloads.serve",
         "--preset", "llama-tiny", "--quant", "none",
         "--port", str(port)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 90
        while time.time() < deadline:
            if p.poll() is not None:
                pytest.fail(f"serve exited rc={p.returncode}")
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz",
                        timeout=2) as r:
                    if r.status == 200:
                        break
            except OSError:
                time.sleep(0.5)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"tokens": [1, 2], "steps": 2, "stream": True})
        assert ei.value.code == 400
        assert b"requires --engine" in ei.value.read()
    finally:
        p.send_signal(signal.SIGINT)
        try:
            p.wait(20)
        except subprocess.TimeoutExpired:
            p.kill()


def test_per_request_sampling_override(serve_proc):
    # the replica's flags default to greedy; a request carrying
    # temperature/top_p samples, and a plain request on the same
    # replica still gets the deterministic greedy stream
    port = serve_proc
    greedy1 = _post(port, {"tokens": [7, 3, 9], "steps": 6})
    sampled = _post(port, {"tokens": [7, 3, 9], "steps": 6,
                           "temperature": 1.8, "top_p": 0.9})
    greedy2 = _post(port, {"tokens": [7, 3, 9], "steps": 6})
    assert greedy1 == greedy2                  # greedy path untouched
    assert len(sampled["tokens"][0]) == 9
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, {"tokens": [1, 2], "steps": 2, "top_p": 1.7})
    assert ei.value.code == 400
    # an explicit nucleus directive on a greedy request would be
    # silently discarded by the argmax branch: refused instead
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, {"tokens": [1, 2], "steps": 2, "top_p": 0.9})
    assert ei.value.code == 400


def test_per_request_eos_over_http(serve_proc):
    port = serve_proc
    prompt = [7, 3, 9]
    steps = 8
    full = _post(port, {"tokens": prompt, "steps": steps})["tokens"][0]
    gen = full[len(prompt):]
    # a stop token must not already appear earlier in the stream, or it
    # fires at its first occurrence; pick one whose FIRST occurrence is
    # mid-stream (the untrained model can repeat tokens)
    stop_at = next((i for i, t in enumerate(gen) if t not in gen[:i]
                    and i > 0), None)
    if stop_at is None:
        pytest.skip("stream repeats one token; no mid-stream stop")
    stopped = _post(port, {"tokens": prompt, "steps": steps,
                           "eos_id": gen[stop_at]})["tokens"][0]
    assert stopped == full[:len(prompt) + stop_at + 1]
    again = _post(port, {"tokens": prompt, "steps": steps})["tokens"][0]
    assert again == full                       # co-tenants unaffected


def test_metrics_scrape(serve_proc):
    port = serve_proc
    _post(port, {"tokens": [6, 6], "steps": 3})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "tpushare_serve_requests_total" in text
    # engine mode exposes slot occupancy; all slots idle between requests
    assert 'tpushare_serve_engine_slots{state="free"} 4.0' in text
    # generated tokens counted (excludes echoed prompts)
    tok = [l for l in text.splitlines()
           if l.startswith("tpushare_serve_tokens_generated_total ")]
    assert tok and float(tok[0].split()[-1]) >= 3


def test_oversized_request_is_rejected_not_fatal(serve_proc):
    port = serve_proc
    bad = [1] * (MAX_LEN + 1)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, {"tokens": bad, "steps": 4})
    assert ei.value.code == 400
    # non-positive steps rejected on every path (a negative value would
    # drive the monotonic token counter backwards on the plain path)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, {"tokens": [1, 2], "steps": -3})
    assert ei.value.code == 400
    # server still serves afterwards
    ok = _post(port, {"tokens": [1, 2], "steps": 2})["tokens"]
    assert len(ok[0]) == 4


def test_rolling_engine_replica():
    """--engine --rolling-kv end to end: continuous batching with
    O(window) slot HBM. Generation runs past the ring length and the
    wire result is bitwise the in-process rolling engine's."""
    import dataclasses
    port = _free_port()
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    p = subprocess.Popen(
        [sys.executable, "-m", "tpushare.workloads.serve",
         "--preset", "llama-tiny", "--quant", "none", "--engine",
         "--engine-slots", "2", "--engine-max-len", "16",
         "--attn-window", "8", "--rolling-kv",
         "--engine-quantum", "4", "--port", str(port)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 90
        while time.time() < deadline:
            if p.poll() is not None:
                pytest.fail(f"serve exited rc={p.returncode}: "
                            f"{p.stdout.read()[-2000:]}")
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz",
                        timeout=2) as r:
                    if r.status == 200:
                        break
            except OSError:
                time.sleep(0.5)
        else:
            pytest.fail("rolling serve never became healthy")
        prompts, steps = [[3, 141, 59], [9, 9, 2, 7, 1]], 40
        got = _post(port, {"tokens": prompts, "steps": steps},
                    timeout=300)["tokens"]
        cfg = dataclasses.replace(PRESETS["llama-tiny"],
                                  attn_window=8).validate()
        params = init_params(cfg, jax.random.key(0))
        eng = DecodeEngine(params, cfg, max_slots=2, max_len=16,
                           quantum=4, rolling=True)
        rids = [eng.submit(pr, steps) for pr in prompts]
        done = eng.drain()
        want = [pr + done[r] for pr, r in zip(prompts, rids)]
        assert got == want
    finally:
        p.send_signal(signal.SIGINT)
        try:
            p.wait(20)
        except subprocess.TimeoutExpired:
            p.kill()  # CPU-only child: no TPU claim to wedge
